"""Shared text helpers: edit distance.

Behavioral parity: /root/reference/torchmetrics/functional/text/helper.py
(_edit_distance :333-350). Host-side string processing — strings never enter
XLA; only the integer statistics land on device. The O(n*m) dynamic program
runs in the in-repo C++ core (metrics_tpu/native/edit_distance.cpp) when the
toolchain is available, with a pure-Python two-row DP as the fallback.
"""
from typing import Dict, List, Sequence, Tuple

import numpy as np

from metrics_tpu.native import levenshtein_batch_ids, levenshtein_ids, native_available


def _tokens_to_ids(*seqs: Sequence) -> List[np.ndarray]:
    """Map token sequences to shared int32 ids (identity-preserving)."""
    vocab: Dict = {}
    out = []
    for seq in seqs:
        ids = np.empty(len(seq), dtype=np.int32)
        for i, tok in enumerate(seq):
            ids[i] = vocab.setdefault(tok, len(vocab))
        out.append(ids)
    return out


def _edit_distance_py(prediction_tokens: Sequence, reference_tokens: Sequence) -> int:
    """Levenshtein distance between two token sequences (two-row DP).

    Plain-Python rows beat a numpy-vectorized row at every size (the
    cur[j-1] dependency forces a Python inner loop either way), measured
    2-2.5x across L=10..800.
    """
    n, m = len(prediction_tokens), len(reference_tokens)
    if n == 0:
        return m
    if m == 0:
        return n
    prev_row = list(range(m + 1))
    for i, p_tok in enumerate(prediction_tokens, 1):
        cur = [i]
        for j, r_tok in enumerate(reference_tokens, 1):
            cur.append(min(prev_row[j] + 1, cur[j - 1] + 1, prev_row[j - 1] + (p_tok != r_tok)))
        prev_row = cur
    return prev_row[m]


def _edit_distance(prediction_tokens: Sequence, reference_tokens: Sequence) -> int:
    """Levenshtein distance between two token sequences (native when available)."""
    if native_available():
        try:
            a, b = _tokens_to_ids(prediction_tokens, reference_tokens)
        except TypeError:
            pass  # unhashable tokens — the ==-based Python DP still applies
        else:
            dist = levenshtein_ids(a, b)
            if dist is not None:
                return dist
    return _edit_distance_py(prediction_tokens, reference_tokens)


def _edit_distances(pairs: Sequence[Tuple[Sequence, Sequence]]) -> List[int]:
    """Edit distances for many pairs — one native call for the whole batch."""
    if native_available() and pairs:
        try:
            seqs = _tokens_to_ids(*(s for pair in pairs for s in pair))
        except TypeError:
            pass
        else:
            out = levenshtein_batch_ids(seqs[0::2], seqs[1::2])
            if out is not None:
                return [int(v) for v in out]
    return [_edit_distance_py(a, b) for a, b in pairs]
