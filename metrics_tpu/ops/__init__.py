"""TPU-native fused kernels (Pallas) for hot metric ops.

Every kernel here is bit-exact with the plain XLA formulation that the
metrics dispatch by default (measured faster — see binned_stats.py module
docstring for numbers). Set ``METRICS_TPU_FORCE_PALLAS=1`` to opt in to the
Pallas path on TPU backends; off-TPU the kernels run in interpret mode for
parity testing.
"""
from metrics_tpu.ops.binned_stats import binned_stat_scores, pallas_enabled

__all__ = ["binned_stat_scores", "pallas_enabled"]
