"""Windowed wrapper coverage (metrics_tpu/streaming/window.py).

The two acceptance pins of the streaming subsystem live here: (1) a
1k-step ``SlidingWindow(Accuracy, window=64)`` stream is ZERO retraces
after the warmup compile and every state leaf keeps a fixed shape
(jaxpr-verified through ``jax.eval_shape``); (2) windowed results are
**bit-identical** to an oracle that rebuilds a fresh inner metric from
the window's raw updates (exact for slide=1; at slide>1 the oracle
replays the wrapper's bucket bookkeeping so fp grouping matches).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import (
    Accuracy,
    CatMetric,
    MaxMetric,
    MeanMetric,
    MeanSquaredError,
    SumMetric,
    profiling,
)
from metrics_tpu.streaming import ExponentialDecay, SlidingWindow, TumblingWindow
from metrics_tpu.utilities.exceptions import MetricsUserError

_C = 4


def _acc():
    return Accuracy(num_classes=_C, average="macro")


def _batch(rng, b=8):
    return (
        jnp.asarray(rng.rand(b, _C).astype(np.float32)),
        jnp.asarray(rng.randint(0, _C, b)),
    )


# --------------------------------------------------------------- sliding
def test_sliding_sum_matches_oracle_slide1():
    """slide=1: the value over floats is bit-identical to a fresh metric
    fed exactly the last `window` updates (the fold adds exact 0.0
    defaults and accumulates in stream order)."""
    w = SlidingWindow(SumMetric(), window=3, jit_update=False)
    vals = [1.1, 2.2, 4.4, 8.8, 17.6, 0.3]
    for i, v in enumerate(vals):
        w.update(jnp.asarray(v))
        oracle = SumMetric()
        for u in vals[max(0, i - 2): i + 1]:
            oracle.update(jnp.asarray(u))
        np.testing.assert_array_equal(np.asarray(w.compute()), np.asarray(oracle.compute()))


def test_sliding_accuracy_matches_oracle_slide2():
    """slide>1: integer-count states (Accuracy tp/fp/...) are exact under
    any grouping, so the oracle replays the wrapper's bucket layout and
    the confusion counts must agree bitwise at every step."""
    rng = np.random.RandomState(0)
    n_buckets, slide = 2, 2
    w = SlidingWindow(_acc(), window=4, slide=slide, jit_update=False)
    cursor, in_bucket = 0, 0
    buckets = [[] for _ in range(n_buckets)]
    for _ in range(9):
        p, t = _batch(rng)
        if in_bucket >= slide:
            cursor = (cursor + 1) % n_buckets
            buckets[cursor] = []
            in_bucket = 0
        buckets[cursor].append((p, t))
        in_bucket += 1
        w.update(p, t)
        oracle = _acc()
        for b in [(cursor + 1 + j) % n_buckets for j in range(n_buckets)]:
            for pp, tt in buckets[b]:
                oracle.update(pp, tt)
        np.testing.assert_array_equal(np.asarray(w.compute()), np.asarray(oracle.compute()))


def test_sliding_zero_retraces_1k_steps_and_fixed_leaf_shapes():
    """Acceptance pin: after the warmup compile, 1000 engine updates of
    SlidingWindow(Accuracy, window=64) are 1000 cached dispatches and
    ZERO retraces, and pure_update's output avals equal its input avals
    (the jaxpr proof that the ring never changes shape)."""
    rng = np.random.RandomState(1)
    w = SlidingWindow(_acc(), window=64, jit_update=True)
    p, t = _batch(rng, b=16)
    w.update(p, t)  # warmup compile
    jax.block_until_ready(w.cursor)
    with profiling.track_dispatches() as tr:
        for _ in range(1000):
            w.update(p, t)
        jax.block_until_ready(w.cursor)
    assert tr.retrace_count() == 0
    assert tr.dispatch_count() == 1000

    state = w.default_state()
    out = jax.eval_shape(w.pure_update, state, p, t)
    assert {k: (v.shape, v.dtype) for k, v in out.items()} == {
        k: (v.shape, v.dtype) for k, v in state.items()
    }


def test_sliding_jit_pure_update_matches_eager():
    rng = np.random.RandomState(2)
    w = SlidingWindow(_acc(), window=4, slide=2, jit_update=False)
    state = w.default_state()
    jit_up = jax.jit(w.pure_update)
    for _ in range(6):
        p, t = _batch(rng)
        state = jit_up(state, p, t)
        w.update(p, t)
    for k in state:
        np.testing.assert_array_equal(np.asarray(state[k]), np.asarray(getattr(w, k)))


def test_sliding_masked_update_padded_lane_is_noop():
    """A fully-padded serve lane must neither advance the cursor nor count
    an update — the stacked launcher vmaps _masked_update over real and
    padded rows alike."""
    rng = np.random.RandomState(3)
    w = SlidingWindow(_acc(), window=2, jit_update=False)
    p, t = _batch(rng)
    w.update(p, t)
    before = {k: np.asarray(getattr(w, k)) for k in w.default_state()}
    w._masked_update(jnp.zeros(p.shape[0], bool), p, t)
    for k, v in before.items():
        np.testing.assert_array_equal(np.asarray(getattr(w, k)), v)


def test_sliding_forward_batch_value_matches_fresh_metric():
    """full_state_update=True: forward's batch value is the inner metric
    evaluated on just this batch."""
    rng = np.random.RandomState(4)
    w = SlidingWindow(_acc(), window=4, jit_update=False)
    p, t = _batch(rng)
    batch_val = w.forward(p, t)
    fresh = _acc()
    fresh.update(p, t)
    np.testing.assert_allclose(np.asarray(batch_val), np.asarray(fresh.compute()), rtol=1e-6)


def test_sliding_reset_restores_defaults():
    rng = np.random.RandomState(5)
    w = SlidingWindow(_acc(), window=2, jit_update=False)
    w.update(*_batch(rng))
    w.reset()
    for k, v in w.default_state().items():
        np.testing.assert_array_equal(np.asarray(getattr(w, k)), np.asarray(v))


# -------------------------------------------------------------- tumbling
def test_tumbling_semantics():
    w = TumblingWindow(SumMetric(), window=2, jit_update=False)
    w.update(jnp.asarray(1.0))
    assert float(w.compute()) == 1.0  # partial current window before any completes
    w.update(jnp.asarray(2.0))
    assert float(w.compute()) == 3.0  # first window sealed
    w.update(jnp.asarray(4.0))
    assert float(w.compute()) == 3.0  # still the last COMPLETED window
    w.update(jnp.asarray(8.0))
    assert float(w.compute()) == 12.0  # second window sealed


def test_tumbling_jit_parity():
    rng = np.random.RandomState(6)
    w = TumblingWindow(_acc(), window=3, jit_update=False)
    state = w.default_state()
    jit_up = jax.jit(w.pure_update)
    for _ in range(7):
        p, t = _batch(rng)
        state = jit_up(state, p, t)
        w.update(p, t)
    for k in state:
        np.testing.assert_array_equal(np.asarray(state[k]), np.asarray(getattr(w, k)))


# ----------------------------------------------------------------- decay
def test_decay_matches_closed_form():
    m = ExponentialDecay(MeanMetric(), halflife=10.0, jit_update=False)
    d = 0.5 ** (1.0 / 10.0)
    num = den = 0.0
    for v in (1.0, 2.0, 3.0, -1.0):
        m.update(jnp.asarray(v))
        num = d * num + v
        den = d * den + 1.0
    np.testing.assert_allclose(float(m.compute()), num / den, rtol=1e-6)


def test_decay_recent_updates_dominate():
    m = ExponentialDecay(MeanSquaredError(), halflife=2.0, jit_update=False)
    rng = np.random.RandomState(7)
    t = jnp.asarray(rng.rand(16).astype(np.float32))
    for _ in range(20):
        m.update(t + 1.0, t)  # old regime: error 1.0
    for _ in range(20):
        m.update(t, t)  # new regime: error 0.0
    assert float(m.compute()) < 0.01  # halflife 2 -> old regime decayed away


# ------------------------------------------------------------ validation
def test_wrappers_reject_list_state_inner():
    with pytest.raises(MetricsUserError, match="list state"):
        SlidingWindow(CatMetric(), window=4)


def test_sliding_rejects_bad_geometry():
    with pytest.raises(MetricsUserError, match="positive multiple"):
        SlidingWindow(SumMetric(), window=5, slide=2)


def test_decay_rejects_max_min_reductions():
    with pytest.raises(MetricsUserError, match="max/min"):
        ExponentialDecay(MaxMetric(), halflife=4.0)


def test_wrappers_reject_non_metric():
    with pytest.raises(MetricsUserError, match="expects a Metric"):
        TumblingWindow(lambda: None, window=4)


def test_inner_spec_distinguishes_configs():
    """The AOT persistent-cache namespace must see different inner metrics
    (the inner lives under an underscore attr, which owner_namespace
    skips — inner_spec is the public mirror)."""
    a = SlidingWindow(_acc(), window=4)
    b = SlidingWindow(Accuracy(num_classes=8, average="macro"), window=4)
    assert a.inner_spec != b.inner_spec
