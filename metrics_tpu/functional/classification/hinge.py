"""Hinge loss functional implementation.

Behavioral parity: /root/reference/torchmetrics/functional/classification/
hinge.py (231 LoC). Boolean mask-indexing is replaced by where/one-hot
selections so the whole update is jit-clean with static shapes.
"""
from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.utilities.checks import _input_squeeze
from metrics_tpu.utilities.data import to_onehot
from metrics_tpu.utilities.enums import DataType, EnumStr

Array = jax.Array


class MulticlassMode(EnumStr):
    """Multiclass flavours of hinge loss (ref hinge.py:24-33)."""

    CRAMMER_SINGER = "crammer-singer"
    ONE_VS_ALL = "one-vs-all"


def _check_shape_and_type_consistency_hinge(preds: Array, target: Array) -> DataType:
    """Parity: ref hinge.py:36-72."""
    if target.ndim > 1:
        raise ValueError(f"The `target` should be one dimensional, got `target` with shape={target.shape}.")
    if preds.ndim == 1:
        if preds.shape != target.shape:
            raise ValueError(
                "The `preds` and `target` should have the same shape,"
                f" got `preds` with shape={preds.shape} and `target` with shape={target.shape}."
            )
        mode = DataType.BINARY
    elif preds.ndim == 2:
        if preds.shape[0] != target.shape[0]:
            raise ValueError(
                "The `preds` and `target` should have the same shape in the first dimension,"
                f" got `preds` with shape={preds.shape} and `target` with shape={target.shape}."
            )
        mode = DataType.MULTICLASS
    else:
        raise ValueError(f"The `preds` should be one or two dimensional, got `preds` with shape={preds.shape}.")
    return mode


def _hinge_update(
    preds: Array,
    target: Array,
    squared: bool = False,
    multiclass_mode: Optional[Union[str, MulticlassMode]] = None,
) -> Tuple[Array, Array]:
    """Sum of per-observation hinge losses + count (ref hinge.py:75-139)."""
    preds, target = _input_squeeze(preds, target)
    mode = _check_shape_and_type_consistency_hinge(preds, target)

    if mode == DataType.MULTICLASS:
        target_oh = to_onehot(target, max(2, preds.shape[1])).astype(bool)

    if mode == DataType.MULTICLASS and (multiclass_mode is None or multiclass_mode == MulticlassMode.CRAMMER_SINGER):
        # margin = score of true class - best score among other classes
        margin = jnp.sum(jnp.where(target_oh, preds, 0.0), axis=1)
        margin = margin - jnp.max(jnp.where(target_oh, -jnp.inf, preds), axis=1)
    elif mode == DataType.BINARY or multiclass_mode == MulticlassMode.ONE_VS_ALL:
        if mode == DataType.BINARY:
            target_b = target.astype(bool)
        else:
            target_b = target_oh
        margin = jnp.where(target_b, preds, -preds)
    else:
        raise ValueError(
            "The `multiclass_mode` should be either None / 'crammer-singer' / MulticlassMode.CRAMMER_SINGER"
            "(default) or 'one-vs-all' / MulticlassMode.ONE_VS_ALL,"
            f" got {multiclass_mode}."
        )

    measures = jnp.clip(1 - margin, min=0)
    if squared:
        measures = measures**2

    total = jnp.asarray(target.shape[0])
    return measures.sum(axis=0), total


def _hinge_compute(measure: Array, total: Array) -> Array:
    """Mean hinge loss (ref hinge.py:142-157)."""
    return measure / total


def hinge_loss(
    preds: Array,
    target: Array,
    squared: bool = False,
    multiclass_mode: Optional[Union[str, MulticlassMode]] = None,
) -> Array:
    """Mean Hinge loss, typically for SVMs (ref hinge.py:160-231).

    Example (binary):
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import hinge_loss
        >>> target = jnp.asarray([0, 1, 1])
        >>> preds = jnp.asarray([-2.2, 2.4, 0.1])
        >>> round(float(hinge_loss(preds, target)), 4)
        0.3
    """
    measure, total = _hinge_update(preds, target, squared=squared, multiclass_mode=multiclass_mode)
    return _hinge_compute(measure, total)
