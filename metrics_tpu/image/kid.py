"""Kernel Inception Distance with an injectable feature extractor.

Behavioral parity: /root/reference/torchmetrics/image/kid.py (282 LoC).
"""
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.metric import Metric
from metrics_tpu.utilities.data import dim_zero_cat

Array = jax.Array


def maximum_mean_discrepancy(k_xx: Array, k_xy: Array, k_yy: Array) -> Array:
    """Unbiased MMD estimate from kernel matrices (ref kid.py:29-46)."""
    m = k_xx.shape[0]
    kt_xx_sum = (k_xx.sum(axis=-1) - jnp.diag(k_xx)).sum()
    kt_yy_sum = (k_yy.sum(axis=-1) - jnp.diag(k_yy)).sum()
    k_xy_sum = k_xy.sum()

    value = (kt_xx_sum + kt_yy_sum) / (m * (m - 1))
    value -= 2 * k_xy_sum / (m**2)
    return value


def poly_kernel(f1: Array, f2: Array, degree: int = 3, gamma: Optional[float] = None, coef: float = 1.0) -> Array:
    """Polynomial kernel matrix (ref kid.py:49-54)."""
    if gamma is None:
        gamma = 1.0 / f1.shape[1]
    return (f1 @ f2.T * gamma + coef) ** degree


def poly_mmd(
    f_real: Array, f_fake: Array, degree: int = 3, gamma: Optional[float] = None, coef: float = 1.0
) -> Array:
    """Polynomial-kernel MMD (ref kid.py:57-64)."""
    k_11 = poly_kernel(f_real, f_real, degree, gamma, coef)
    k_22 = poly_kernel(f_fake, f_fake, degree, gamma, coef)
    k_12 = poly_kernel(f_real, f_fake, degree, gamma, coef)
    return maximum_mean_discrepancy(k_11, k_12, k_22)


class KernelInceptionDistance(Metric):
    """KID: polynomial MMD over random feature subsets (ref kid.py:67-282).

    Args:
        feature_dim: together with ``max_samples``, switches the states from
            growing feature **lists** (the reference's design) to a
            **fixed-capacity preallocated buffer** ``(max_samples,
            feature_dim)`` plus a fill count. Same accumulated features, so
            ``compute()`` is bit-identical to the list path — but the state
            pytree has a static shape: updates jit/scan without
            per-update-count recompiles, states donate cleanly, and sync
            stacks a single buffer per device instead of a ragged list.
            Eager updates past capacity raise; traced updates clamp to the
            tail (XLA ``dynamic_update_slice`` semantics), so size
            ``max_samples`` to bound the stream. By default ``compute()``
            stays eager-only in both layouts — it slices the buffer by the
            concrete fill count and draws subsets from the host RNG stream
            (reference-identical indices, ref kid.py:262-270), neither of
            which can trace; pass ``compute_rng_key`` for a fully
            in-graph compute.
        max_samples: buffer capacity (rows) for the fixed-shape path.
        compute_rng_key: opt-in (buffer path only): an int seed or
            ``jax.random`` key that moves subset sampling in-graph, making
            ``compute``/``pure_compute`` fully jit-compatible (e.g. KID at
            the end of a compiled eval epoch). Subset indices then come
            from ``jax.random``, NOT the reference's ``np.random`` stream
            — same estimator distribution, different draws — and an
            under-filled side poisons the outputs with NaN instead of
            raising (tracing cannot raise). See ``_compute_in_graph``.
        feature: reference-style selector for the bundled InceptionV3
            extractor (ref kid.py:169-199): 64 / 192 / 768 / 2048 tap
            width or ``'logits_unbiased'``. Mutually exclusive with
            ``feature_extractor``.
        weights_path: local ``.npz`` of converted InceptionV3 weights for
            the bundled extractor; implies ``feature=2048`` when
            ``feature`` is not given.

    Example (pre-extracted features):
        >>> import jax, jax.numpy as jnp
        >>> from metrics_tpu.image.kid import KernelInceptionDistance
        >>> kid = KernelInceptionDistance(subsets=3, subset_size=32)
        >>> key1, key2 = jax.random.split(jax.random.PRNGKey(0))
        >>> kid.update(jax.random.normal(key1, (64, 8)), real=True)
        >>> kid.update(jax.random.normal(key2, (64, 8)) + 1.0, real=False)
        >>> mean, std = kid.compute()
        >>> float(mean) > 0
        True
    """

    is_differentiable = False
    higher_is_better = False
    full_state_update = False

    def __init__(
        self,
        feature_extractor: Optional[Callable[[Array], Array]] = None,
        subsets: int = 100,
        subset_size: int = 1000,
        degree: int = 3,
        gamma: Optional[float] = None,
        coef: float = 1.0,
        reset_real_features: bool = True,
        feature_dim: Optional[int] = None,
        max_samples: Optional[int] = None,
        compute_rng_key: Optional[Any] = None,
        feature: Optional[Any] = None,
        weights_path: Optional[str] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if feature is not None or weights_path is not None:
            # reference-style bundled-extractor selection (ref kid.py:169-199)
            from metrics_tpu.image.inception_net import resolve_ctor_extractor

            feature_extractor = resolve_ctor_extractor(
                feature_extractor, feature, weights_path, default_output=2048,
                # ref kid.py:190-199 valid set
                allowed=("logits_unbiased", 64, 192, 768, 2048),
            )
        self.feature_extractor = feature_extractor

        if not (isinstance(subsets, int) and subsets > 0):
            raise ValueError("Argument `subsets` expected to be integer larger than 0")
        self.subsets = subsets
        if not (isinstance(subset_size, int) and subset_size > 0):
            raise ValueError("Argument `subset_size` expected to be integer larger than 0")
        self.subset_size = subset_size
        if not (isinstance(degree, int) and degree > 0):
            raise ValueError("Argument `degree` expected to be integer larger than 0")
        self.degree = degree
        if gamma is not None and not (isinstance(gamma, float) and gamma > 0):
            raise ValueError("Argument `gamma` expected to be `None` or float larger than 0")
        self.gamma = gamma
        if not (isinstance(coef, float) and coef > 0):
            raise ValueError("Argument `coef` expected to be float larger than 0")
        self.coef = coef
        if not isinstance(reset_real_features, bool):
            raise ValueError("Argument `reset_real_features` expected to be a bool")
        self.reset_real_features = reset_real_features
        if (feature_dim is None) != (max_samples is None):
            raise ValueError("Arguments `feature_dim` and `max_samples` must be given together")
        if feature_dim is not None and not (isinstance(feature_dim, int) and feature_dim > 0):
            raise ValueError("Argument `feature_dim` expected to be `None` or a positive integer")
        if max_samples is not None and not (isinstance(max_samples, int) and max_samples > 0):
            raise ValueError("Argument `max_samples` expected to be `None` or a positive integer")
        self.feature_dim = feature_dim
        self.max_samples = max_samples
        if compute_rng_key is not None:
            if feature_dim is None:
                raise ValueError(
                    "Argument `compute_rng_key` requires the fixed-shape buffer path"
                    " (`feature_dim=`/`max_samples=`): the list path has no static"
                    " bound to sample under jit"
                )
            from metrics_tpu.utilities.checks import as_rng_key

            compute_rng_key = as_rng_key(compute_rng_key, "compute_rng_key")
            if subset_size > max_samples:
                raise ValueError(
                    f"Argument `subset_size` ({subset_size}) cannot exceed `max_samples`"
                    f" ({max_samples}) when `compute_rng_key` is set (the in-graph draw"
                    " samples from the fixed buffer)"
                )
        self.compute_rng_key = compute_rng_key

        if feature_dim is None:
            self.add_state("real_features", [], dist_reduce_fx=None)
            self.add_state("fake_features", [], dist_reduce_fx=None)
        else:
            dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
            for prefix in ("real", "fake"):
                self.add_state(f"{prefix}_buffer", jnp.zeros((max_samples, feature_dim), dtype), dist_reduce_fx=None)
                self.add_state(f"{prefix}_count", jnp.zeros((), jnp.int32), dist_reduce_fx=None)
            # raw sample rows: exempt from sync_dtype compression (permanent)
            self._sample_state_names = {"real_buffer", "fake_buffer"}

    def update(self, imgs: Array, real: bool) -> None:
        features = self.feature_extractor(imgs) if self.feature_extractor is not None else imgs
        if self.feature_dim is not None:
            if features.ndim != 2 or features.shape[1] != self.feature_dim:
                raise ValueError(
                    f"Expected extracted features of shape (N, {self.feature_dim}), got {features.shape}"
                )
            prefix = "real" if real else "fake"
            buf, count = getattr(self, f"{prefix}_buffer"), getattr(self, f"{prefix}_count")
            if not isinstance(count, jax.core.Tracer) and int(count) + features.shape[0] > self.max_samples:
                raise ValueError(
                    f"KID buffer overflow: {int(count)} + {features.shape[0]} samples exceed"
                    f" `max_samples={self.max_samples}`"
                )
            buf = jax.lax.dynamic_update_slice(
                buf, features.astype(buf.dtype), (count, jnp.zeros((), count.dtype))
            )
            # under jit the eager raise above is skipped and the clamped
            # write would silently overwrite the tail — NaN-poison instead
            # so compute() surfaces the overflow (same policy as merge);
            # eagerly the raise already fired, so skip the dead full-buffer
            # add there
            if isinstance(count, jax.core.Tracer):
                overflow = count + features.shape[0] > self.max_samples
                buf = buf + jnp.where(overflow, jnp.asarray(jnp.nan, buf.dtype), 0)
            setattr(self, f"{prefix}_buffer", buf)
            setattr(self, f"{prefix}_count", count + features.shape[0])
        elif real:
            self.real_features.append(features)
        else:
            self.fake_features.append(features)

    def _reduce_states(self, incoming_state) -> None:
        """Merge an incoming buffer-mode state by compaction, not stacking.

        The base class stacks ``dist_reduce_fx=None`` tensor states (the
        cross-device sync layout); for ``pure_merge``/``forward`` that would
        corrupt the fixed-capacity buffers. Rows at or past each buffer's
        fill count are zero by construction (zero-initialised, updates write
        contiguously from the front, eager overflow raises), so shifting the
        local buffer to start at the incoming count and adding merges the
        two streams in order. The shift masks rather than wraps, so local
        rows past capacity can never alias onto valid incoming rows.
        Merged totals must fit ``max_samples``: eagerly that raises; under
        ``jit`` (where raising is impossible) the merged buffer is
        NaN-poisoned so ``compute()`` surfaces NaN instead of a silently
        truncated value.
        """
        if self.feature_dim is None:
            return super()._reduce_states(incoming_state)
        for prefix in ("real", "fake"):
            g_buf = incoming_state[f"{prefix}_buffer"]
            g_cnt = incoming_state[f"{prefix}_count"]
            l_buf = getattr(self, f"{prefix}_buffer")
            l_cnt = getattr(self, f"{prefix}_count")
            traced = isinstance(g_cnt, jax.core.Tracer) or isinstance(l_cnt, jax.core.Tracer)
            if not traced and int(g_cnt) + int(l_cnt) > self.max_samples:
                raise ValueError(
                    f"KID buffer overflow on merge: {int(g_cnt)} + {int(l_cnt)} samples"
                    f" exceed `max_samples={self.max_samples}`"
                )
            idx = jnp.arange(self.max_samples) - g_cnt
            shifted = jnp.where(
                ((idx >= 0) & (idx < l_cnt))[:, None],
                l_buf[jnp.clip(idx, 0, self.max_samples - 1)],
                jnp.zeros((), l_buf.dtype),
            )
            merged = g_buf + shifted
            overflow = (g_cnt + l_cnt) > self.max_samples
            merged = merged + jnp.where(overflow, jnp.asarray(jnp.nan, merged.dtype), 0)
            object.__setattr__(self, f"{prefix}_buffer", merged)
            object.__setattr__(self, f"{prefix}_count", g_cnt + l_cnt)

    def _buffered(self, prefix: str) -> Array:
        """Valid rows of a fixed-capacity buffer; flattens a synced stack."""
        buf, count = getattr(self, f"{prefix}_buffer"), getattr(self, f"{prefix}_count")
        if buf.ndim == 3:  # dist-synced: (world, capacity, D) + (world,) counts
            return jnp.concatenate([buf[i, : int(count[i])] for i in range(buf.shape[0])])
        return buf[: int(count)]

    def _compute_in_graph(self) -> Tuple[Array, Array]:
        """Fully traceable buffer-mode compute: in-graph subset sampling.

        Each subset draws ``subset_size`` rows uniformly WITHOUT
        replacement from the valid prefix of the fixed ``(max_samples, D)``
        buffer: valid rows get uniform(0, 1) priorities, invalid rows
        ``-inf``, and ``top_k`` keeps the ``subset_size`` best — a uniform
        random subset of the valid rows, entirely in matmul/sort ops. The
        RNG is ``jax.random`` from the static ``compute_rng_key`` (a
        DOCUMENTED departure from the reference's ``np.random`` stream —
        subset values differ, the estimator's distribution does not; the
        default eager path keeps reference-identical indices). Raising is
        impossible in-graph, so an under-filled side (count <
        subset_size) poisons both outputs with NaN, matching the buffer
        paths' overflow semantics.
        """
        def _flat(prefix: str) -> Tuple[Array, Array, Array]:
            """(rows, valid_mask, total_count) for 2-D or dist-stacked 3-D buffers."""
            buf = getattr(self, f"{prefix}_buffer")
            count = getattr(self, f"{prefix}_count")
            if buf.ndim == 3:  # synced: (world, capacity, D) + (world,) counts
                mask = (jnp.arange(buf.shape[1])[None, :] < count[:, None]).reshape(-1)
                return buf.reshape(-1, buf.shape[-1]), mask, count.sum()
            return buf, jnp.arange(buf.shape[0]) < count, count

        rbuf, rmask, rcnt = _flat("real")
        fbuf, fmask, fcnt = _flat("fake")

        def _subset(key: Array, mask: Array) -> Array:
            priorities = jnp.where(mask, jax.random.uniform(key, mask.shape), -jnp.inf)
            _, idx = jax.lax.top_k(priorities, self.subset_size)
            return idx

        def _one_subset(key: Array) -> Array:
            key_r, key_f = jax.random.split(key)
            return poly_mmd(
                rbuf[_subset(key_r, rmask)], fbuf[_subset(key_f, fmask)],
                self.degree, self.gamma, self.coef,
            )

        scores = jax.lax.map(_one_subset, jax.random.split(self.compute_rng_key, self.subsets))
        underfilled = (rcnt < self.subset_size) | (fcnt < self.subset_size)
        poison = jnp.where(underfilled, jnp.asarray(jnp.nan, scores.dtype), 0.0)
        # ddof=0: the reference's biased std (kid.py:275 `unbiased=False`)
        return scores.mean() + poison, scores.std(ddof=0) + poison

    def compute(self) -> Tuple[Array, Array]:
        """Mean/std of per-subset MMD (ref kid.py:244-275)."""
        if self.feature_dim is not None:
            traced = isinstance(self.real_count, jax.core.Tracer) or isinstance(
                self.fake_count, jax.core.Tracer
            )
            if self.compute_rng_key is not None:
                if not traced:
                    # eager calls CAN raise — give the default path's clear
                    # error instead of the traced path's silent NaN poison
                    for prefix in ("real", "fake"):
                        count = getattr(self, f"{prefix}_count")
                        if int(np.asarray(count).sum()) < self.subset_size:
                            raise ValueError(
                                "Argument `subset_size` should be smaller than the number of samples"
                            )
                return self._compute_in_graph()
            if traced:
                raise ValueError(
                    "KernelInceptionDistance buffer-mode `compute()` under jit needs"
                    " `compute_rng_key=` (in-graph jax.random subset sampling); the"
                    " default path keeps the reference's host np.random stream,"
                    " which cannot trace"
                )
            real_features = self._buffered("real")
            fake_features = self._buffered("fake")
        else:
            real_features = dim_zero_cat(self.real_features)
            fake_features = dim_zero_cat(self.fake_features)

        n_samples_real = real_features.shape[0]
        if n_samples_real < self.subset_size:
            raise ValueError("Argument `subset_size` should be smaller than the number of samples")
        n_samples_fake = fake_features.shape[0]
        if n_samples_fake < self.subset_size:
            raise ValueError("Argument `subset_size` should be smaller than the number of samples")

        # Subset draws keep the reference's host RNG stream (np.random, one
        # permutation per subset per side, ref kid.py:262-270 — identical
        # indices; f32 results match the eager loop to ~1e-5 relative, the
        # compiled map accumulating matmuls in a different order), but the
        # scoring is ONE compiled program:
        # the indices upload as a single (subsets, k) batch and `lax.map`
        # runs the three-kernel MMD per subset device-side. The eager loop
        # paid `subsets` gather/dispatch round trips; this pays one (the
        # device-side loop bounds peak memory at a single (k, k) kernel
        # triplet, where a vmap would materialize all `subsets` of them).
        draws = [
            (
                np.random.permutation(n_samples_real)[: self.subset_size],
                np.random.permutation(n_samples_fake)[: self.subset_size],
            )
            for _ in range(self.subsets)
        ]  # real/fake interleaved per subset: the reference's exact RNG stream
        idx_real = np.stack([d[0] for d in draws])
        idx_fake = np.stack([d[1] for d in draws])

        def _one_subset(idx: Tuple[Array, Array]) -> Array:
            ir, if_ = idx
            return poly_mmd(real_features[ir], fake_features[if_], self.degree, self.gamma, self.coef)

        kid_scores = jax.lax.map(_one_subset, (jnp.asarray(idx_real), jnp.asarray(idx_fake)))
        # ddof=0: the reference's biased std (kid.py:275 `unbiased=False`)
        return kid_scores.mean(), kid_scores.std(ddof=0)

    def reset(self) -> None:
        if not self.reset_real_features:
            self._reset_preserving("real")
        else:
            super().reset()
