"""F-beta and F1 functional implementations.

Behavioral parity: /root/reference/torchmetrics/functional/classification/
f_beta.py (354 LoC).
"""
import numbers
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.helpers import _safe_divide
from metrics_tpu.functional.classification.stat_scores import _reduce_stat_scores, _stat_scores_update
from metrics_tpu.utilities.enums import AverageMethod, MDMCAverageMethod

Array = jax.Array


def _fbeta_compute(
    tp: Array,
    fp: Array,
    tn: Array,
    fn: Array,
    beta: float,
    ignore_index: Optional[int],
    average: Optional[str],
    mdmc_average: Optional[str],
) -> Array:
    """F-beta from stat scores (ref f_beta.py:31-108)."""
    if average == AverageMethod.MICRO and mdmc_average != MDMCAverageMethod.SAMPLEWISE:
        mask = tp >= 0
        tp_s = jnp.where(mask, tp, 0).sum().astype(jnp.float32)
        fp_s = jnp.where(mask, fp, 0).sum().astype(jnp.float32)
        fn_s = jnp.where(mask, fn, 0).sum().astype(jnp.float32)
        precision = _safe_divide(tp_s, tp_s + fp_s)
        recall = _safe_divide(tp_s, tp_s + fn_s)
    else:
        precision = _safe_divide(tp.astype(jnp.float32), (tp + fp).astype(jnp.float32))
        recall = _safe_divide(tp.astype(jnp.float32), (tp + fn).astype(jnp.float32))

    num = (1 + beta**2) * precision * recall
    denom = beta**2 * precision + recall
    denom = jnp.where(denom == 0.0, 1.0, denom)  # avoid division by 0

    # classes absent from preds and target are meaningless — mark ignored
    if average == AverageMethod.NONE and mdmc_average != MDMCAverageMethod.SAMPLEWISE:
        cond = (tp | fn | fp) == 0
        if ignore_index is not None:
            cond = cond | (jnp.arange(cond.shape[-1]) == ignore_index)
        num = jnp.where(cond, -1.0, num)
        denom = jnp.where(cond, -1.0, denom)
    elif ignore_index is not None:
        if average not in (AverageMethod.MICRO, AverageMethod.SAMPLES) and mdmc_average == MDMCAverageMethod.SAMPLEWISE:
            num = num.at[..., ignore_index].set(-1.0)
            denom = denom.at[..., ignore_index].set(-1.0)
        elif average not in (AverageMethod.MICRO, AverageMethod.SAMPLES):
            num = num.at[ignore_index, ...].set(-1.0)
            denom = denom.at[ignore_index, ...].set(-1.0)

    if average == AverageMethod.MACRO and mdmc_average != MDMCAverageMethod.SAMPLEWISE:
        cond = (tp + fp + fn == 0) | (tp + fp + fn == -3)
        num = jnp.where(cond, -1.0, num)
        denom = jnp.where(cond, -1.0, denom)

    return _reduce_stat_scores(
        numerator=num,
        denominator=denom,
        weights=None if average != AverageMethod.WEIGHTED else (tp + fn).astype(jnp.float32),
        average=average,
        mdmc_average=mdmc_average,
    )


def fbeta_score(
    preds: Array,
    target: Array,
    beta: float = 1.0,
    average: Optional[str] = "micro",
    mdmc_average: Optional[str] = None,
    ignore_index: Optional[int] = None,
    num_classes: Optional[int] = None,
    threshold: float = 0.5,
    top_k: Optional[int] = None,
    multiclass: Optional[bool] = None,
) -> Array:
    """F-beta score (ref f_beta.py:111-231).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import fbeta_score
        >>> target = jnp.asarray([0, 1, 2, 0, 1, 2])
        >>> preds = jnp.asarray([0, 2, 1, 0, 0, 1])
        >>> round(float(fbeta_score(preds, target, beta=0.5)), 4)
        0.3333
    """
    allowed_average = ("micro", "macro", "weighted", "samples", "none", None)
    if average not in allowed_average:
        raise ValueError(f"The `average` has to be one of {allowed_average}, got {average}.")
    allowed_mdmc_average = (None, "samplewise", "global")
    if mdmc_average not in allowed_mdmc_average:
        raise ValueError(f"The `mdmc_average` has to be one of {allowed_mdmc_average}, got {mdmc_average}.")
    if average in ("macro", "weighted", "none", None) and (not num_classes or num_classes < 1):
        raise ValueError(f"When you set `average` as {average}, you have to provide the number of classes.")
    if num_classes and ignore_index is not None and (not 0 <= ignore_index < num_classes or num_classes == 1):
        raise ValueError(f"The `ignore_index` {ignore_index} is not valid for inputs with {num_classes} classes")

    reduce = "macro" if average in ("weighted", "none", None) else average
    tp, fp, tn, fn = _stat_scores_update(
        preds,
        target,
        reduce=reduce,
        mdmc_reduce=mdmc_average,
        threshold=threshold,
        num_classes=num_classes,
        top_k=top_k,
        multiclass=multiclass,
        ignore_index=ignore_index,
    )
    return _fbeta_compute(tp, fp, tn, fn, beta, ignore_index, average, mdmc_average)


def f1_score(
    preds: Array,
    target: Array,
    beta: float = 1.0,
    average: Optional[str] = "micro",
    mdmc_average: Optional[str] = None,
    ignore_index: Optional[int] = None,
    num_classes: Optional[int] = None,
    threshold: float = 0.5,
    top_k: Optional[int] = None,
    multiclass: Optional[bool] = None,
) -> Array:
    """F1 score = F-beta with beta=1 (ref f_beta.py:234-354).

    ``beta`` is accepted in the reference's positional slot but ignored —
    exactly like the reference, whose ``f1_score`` hardcodes ``1.0`` when
    delegating to ``fbeta_score`` (ref f_beta.py:352-354) — so migrated
    positional call sites keep their meaning. Non-numeric values raise, so
    a pre-slot call site like ``f1_score(preds, target, "macro")`` fails
    loudly instead of silently computing the micro average.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import f1_score
        >>> target = jnp.asarray([0, 1, 2, 0, 1, 2])
        >>> preds = jnp.asarray([0, 2, 1, 0, 0, 1])
        >>> round(float(f1_score(preds, target)), 4)
        0.3333
    """
    # numbers.Real admits numpy/jax scalar floats a migrated call site may
    # pass positionally; the guard exists to catch *strings* (average etc.)
    # landing in the reference's ignored beta slot, not to police dtypes
    if isinstance(beta, bool) or not isinstance(beta, (numbers.Real, jnp.ndarray, np.ndarray)):
        raise ValueError(
            f"Expected argument `beta` to be a float but got {beta!r} — note `f1_score` ignores `beta`"
            f" (it is fixed to 1.0); pass `average`/`num_classes` by keyword"
        )
    return fbeta_score(preds, target, 1.0, average, mdmc_average, ignore_index, num_classes, threshold, top_k, multiclass)
